"""Decode-throughput benchmark. Prints ONE JSON line on stdout.

Measures single-stream greedy decode tokens/sec, p50 TTFT (prefill a
128-token prompt + first decode token), and the effective weight-read
bandwidth (weight bytes touched per decode step / step time) on a
BASELINE.json-shaped model, on whatever devices the runtime exposes (the
driver runs this on one real TPU chip).

vs_baseline: fraction of the BASELINE.json north-star bar — 50 decode
tokens/s/chip (the Llama-3.3-70B-on-v5e-8 target; BASELINE.json
"metric"). The metric name carries the preset, so a 1B run scoring >1 is
expected and self-interpreting; the previous denominator (the reference's
2.02 tok/s on RPi hardware) flattered every preset and is gone.

Env knobs: BENCH_PRESET (default llama-8b — the preset closest to the north-star per-chip load), BENCH_STEPS, BENCH_TP,
BENCH_FORMAT, BENCH_SEQ_LEN, BENCH_SKIP_TTFT, BENCH_BATCH (concurrent-lane
metric, default 4; 0 disables — adds one extra compile + 2x steps of
batch-N decode to the run).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR_TOK_S_PER_CHIP = 50.0  # BASELINE.json: 70B Q40 on v5e-8
BASELINE_DEF = "50 tok/s/chip north star (BASELINE.json 70B-on-v5e-8)"


# single source of the decode weight-read model: obs/cost.py (the startup
# roofline report uses the same figure); re-exported here because the
# bench is its historical home and tests import it from this module
from dllama_tpu.obs.cost import weight_bytes_per_token  # noqa: E402,F401


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def headline_record(
    preset: str,
    weight_format: str,
    kv: str,
    per_chip: float,
    weight_gbs: float,
    fallback: bool,
) -> dict:
    """The one-line headline metric. On CPU fallback the north-star ratio
    is SUPPRESSED (`vs_baseline: null, comparable: false`) — a tunnel
    outage must never produce a figure that pattern-matches a perf
    datapoint in a dashboard; the raw value stays, honestly suffixed."""
    return {
        "metric": (
            f"decode_tok_s_per_chip_{preset.replace('-', '_')}_{weight_format}"
            + ("_kv8" if kv == "int8" else "")
            + ("_cpu_fallback" if fallback else "")
        ),
        "value": round(per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": (
            None if fallback else round(per_chip / NORTH_STAR_TOK_S_PER_CHIP, 3)
        ),
        "comparable": not fallback,
        "baseline_def": BASELINE_DEF,
        "weight_gbs_per_chip": round(weight_gbs, 1),
    }


def bench_summaries(result: dict) -> dict:
    """Split one bench result record into per-section summaries keyed by
    the BENCH_<section> file stem. Only sections that actually ran appear
    (a CPU-fallback run with BENCH_SKIP_TTFT produces DECODE alone)."""
    out: dict = {}
    if "metric" in result:
        decode = {
            k: result[k]
            for k in (
                "metric", "value", "unit", "vs_baseline", "comparable",
                "weight_gbs_per_chip", "step_ms", "error",
            )
            if k in result
        }
        out["DECODE"] = decode
    if result.get("ttft_ms_p50") is not None:
        out["TTFT"] = {"ttft_ms_p50": result["ttft_ms_p50"], "unit": "ms"}
    lanes = {k: v for k, v in result.items() if k.startswith("lanes")}
    if lanes:
        out["LANES"] = {**lanes, "unit": "tokens/s/chip"}
    if result.get("format_sweep_tok_s_per_chip"):
        out["SWEEP"] = {
            "tok_s_per_chip": result["format_sweep_tok_s_per_chip"],
            "unit": "tokens/s/chip",
        }
    if result.get("serving"):
        out["SERVING"] = result["serving"]
    return out


def write_bench_summaries(result: dict, out_dir: str | None = None) -> list:
    """Machine-readable BENCH_<section>.json files next to the repo (or
    BENCH_OUT_DIR) at the end of every run, so the perf trajectory is a
    set of stable file names instead of one JSON line to re-parse. Never
    raises: a read-only disk must not turn a finished measurement into a
    failed run."""
    out_dir = out_dir or os.environ.get("BENCH_OUT_DIR") or "."
    paths = []
    for section, payload in bench_summaries(result).items():
        path = os.path.join(out_dir, f"BENCH_{section}.json")
        try:
            with open(path, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError as e:
            log(f"could not write {path}: {e}")
            continue
        paths.append(path)
    if paths:
        log(f"bench summaries: {', '.join(paths)}")
    return paths


def _cpu_fallback_reexec(reason: str) -> None:
    """Re-exec on CPU with an honest `_cpu_fallback` metric suffix. An
    in-process platform switch deadlocks (a hung plugin probe holds the
    backend-init lock), so a clean re-exec is the only safe path."""
    if not os.environ.get("BENCH_CPU_FALLBACK"):
        print(
            f"accelerator unreachable ({reason}); re-exec on CPU fallback",
            file=sys.stderr,
            flush=True,
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_CPU_FALLBACK"] = "1"
        # big presets are untenable on CPU (the q40 fallback dequantizes
        # per call); the tiny preset keeps the fallback line cheap, and
        # the whole config is forced consistent (an inherited BENCH_TP
        # would fail the 1-device mesh; inherited steps would overrun
        # the shortened cache)
        env["BENCH_PRESET"] = "tiny"
        env["BENCH_SEQ_LEN"] = "64"
        env["BENCH_STEPS"] = "16"
        env["BENCH_TP"] = "1"
        env["BENCH_SKIP_TTFT"] = "1"  # keep the CPU fallback line cheap
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    print(
        json.dumps(
            {
                "metric": "decode_tok_s_per_chip_unavailable",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": None,
                "comparable": False,
                "error": f"accelerator unreachable ({reason})",
            }
        )
    )
    os._exit(0)


def _accelerator_expected() -> bool:
    """True when the environment points at the tunneled TPU (vs a plain
    CPU env, where probing would be pointless ceremony)."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and "axon" not in plats and "tpu" not in plats:
        return False
    return (
        bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        or "axon" in plats
        or "tpu" in plats
    )


def _tunnel_probe_retry() -> bool:
    """Bounded retry-with-reconnect: several SUBPROCESS probes spread over
    minutes before giving up on the accelerator. Round 3's record regressed
    to a CPU fallback because a single in-process 180 s probe hit one
    tunnel blip and could never retry (the hung probe wedges the process's
    backend-init lock forever). A subprocess probe that hangs is killed by
    its timeout without poisoning this process; only after a probe answers
    does this process touch the accelerator itself."""
    import subprocess

    attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "6"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    sleep_s = float(os.environ.get("BENCH_PROBE_SLEEP_S", "60"))
    code = (
        "import jax, jax.numpy as jnp, numpy as np; "
        "x = jnp.ones((256, 256)); "
        "print(float(np.asarray((x @ x).ravel()[0])))"
    )
    for i in range(attempts):
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                timeout=probe_timeout,
                capture_output=True,
            )
            if out.returncode == 0:
                log(
                    f"tunnel probe ok on attempt {i + 1}/{attempts} "
                    f"({time.perf_counter() - t0:.0f}s)"
                )
                return True
            log(
                f"probe attempt {i + 1}/{attempts} rc={out.returncode}: "
                f"{out.stderr[-200:].decode(errors='replace')}"
            )
        except subprocess.TimeoutExpired:
            log(
                f"probe attempt {i + 1}/{attempts} timed out after "
                f"{probe_timeout:.0f}s"
            )
        if i + 1 < attempts:
            time.sleep(sleep_s)
    return False


def _serving_smoke(n_clients: int) -> dict:
    """Serving-load smoke (BENCH_SERVING=N): drive N concurrent streaming
    requests against a tiny synthetic model through the real HTTP server +
    LaneScheduler, then report TTFT/queue-wait from the request traces,
    the /metrics histogram counts, and the instrumentation on/off decode
    overhead (ISSUE 2 acceptance: within 1% — the hooks are one histogram
    observe per block dispatch)."""
    import http.client
    import re
    import tempfile
    import threading

    from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer
    from dllama_tpu.models.synthetic import write_synth_model
    from dllama_tpu.obs import get_registry
    from dllama_tpu.obs.trace import read_jsonl
    from dllama_tpu.runtime.api_server import serve
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.tokenizer import Tokenizer

    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=256)
    d = tempfile.mkdtemp(prefix="bench-serving-")
    model_path = os.path.join(d, "model.m")
    tok_path = os.path.join(d, "tok.t")
    trace_path = os.path.join(d, "trace.jsonl")
    write_synth_model(model_path, cfg, max_seq_len=cfg["seq_len"])
    # byte-level tokenizer padded to the model vocab, specials at the top
    vocab = [bytes([i]) for i in range(256)]
    specials = [b"<s>", b"</s>", b"<|eot|>"]
    while len(vocab) < cfg["vocab_size"] - len(specials):
        vocab.append(f"<pad{len(vocab)}>".encode())
    bos_id = len(vocab)
    vocab += specials
    write_tokenizer(tok_path, TokenizerData(
        vocab=vocab,
        scores=[0.0] * len(vocab),
        bos_id=bos_id,
        add_bos=True,
        eos_token_ids=[bos_id + 1, bos_id + 2],
        chat_template="<|start_header_id|>",  # llama3-shaped template probe
        max_token_length=max(len(v) for v in vocab),
    ))
    tok = Tokenizer(tok_path)
    n_lanes = max(2, n_clients)
    engine = InferenceEngine(
        model_path, tokenizer=tok, batch_size=n_lanes, temperature=0.0
    )
    # a small explicit admission chunk so the churn scenario below pays
    # several chunks per long-prompt admission (the default — the largest
    # prefill bucket, 128 here — would swallow the whole prompt in one)
    # generous SLO targets (the CI host is slow and shared): the point is
    # that the attainment/goodput pipeline produces finite numbers, not
    # that the tiny model meets production latency
    srv = serve(
        engine, tok, host="127.0.0.1", port=0, trace_out=trace_path,
        admission_chunk=32, slo_ttft_ms=60000.0, slo_tpot_ms=5000.0,
    )
    port = srv.server_address[1]
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv.shutdown() below; no handle needed
        target=srv.serve_forever, daemon=True, name="dllama-bench-http"
    ).start()

    def one_request(i: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": f"hello {i}"}],
                "max_tokens": 16, "stream": True,
            }),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        r.read()
        conn.close()

    threads = [
        threading.Thread(
            target=one_request, args=(i,), daemon=True,
            name=f"dllama-bench-client-{i}",
        )
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    def scrape_metrics() -> str:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode("utf-8")
        c.close()
        return text

    def metric_value(text: str, name: str) -> float:
        m = re.search(rf"^{name} ([0-9.eE+-]+)$", text, re.M)
        return float(m.group(1)) if m else 0.0

    # admission-churn scenario (the headline for chunked admission): one
    # victim client streams a long completion while two long-prompt
    # requests are admitted mid-stream; the victim's max/p99 inter-delta
    # gap is what a monolithic prefill would have blown up to the whole
    # prefill time
    victim_arrivals: list[float] = []
    first_delta = threading.Event()

    def victim_request() -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "v"}],
                "max_tokens": 48, "stream": True,
            }),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        while True:
            line = r.readline()
            if not line or b"[DONE]" in line:
                break
            if line.startswith(b"data:"):
                victim_arrivals.append(time.perf_counter())
                first_delta.set()
        conn.close()

    vt = threading.Thread(
        target=victim_request, daemon=True, name="dllama-bench-victim"
    )
    vt.start()
    first_delta.wait(timeout=120)
    pre_churn = scrape_metrics()  # victim admitted; churn not started
    long_prompt = "x" * 120  # ~200 prompt tokens with the chat template

    def churn_request(i: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [
                    {"role": "user", "content": f"{long_prompt}{i}"}
                ],
                "max_tokens": 2,
            }),
            {"Content-Type": "application/json"},
        )
        conn.getresponse().read()
        conn.close()

    churners = [
        threading.Thread(
            target=churn_request, args=(i,), daemon=True,
            name=f"dllama-bench-churn-{i}",
        )
        for i in range(2)
    ]
    for t in churners:
        t.start()
    for t in churners + [vt]:
        t.join()

    # shared-system-prompt fanout (ISSUE 6): N streams share one long
    # system prompt. A warmup request publishes the rendered prefix into
    # the radix tree at finish; the fanned-out streams then admit with
    # most of their prompt ADOPTED from shared pool pages instead of
    # re-prefilled. The same round runs against a sharing-OFF server
    # (kv_page_size=-1) so the TTFT delta is the sharing win, not noise
    # between configs.
    fanout_n = max(3, n_clients)
    sys_prompt = (
        "You are a terse assistant. Answer in one short sentence and "
        "never repeat the question back to the user. "
    )

    def fanout_round(port_: int) -> float | None:
        def one(i: int, out: dict) -> None:
            conn = http.client.HTTPConnection("127.0.0.1", port_, timeout=300)
            t0 = time.perf_counter()
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "system", "content": sys_prompt},
                        {"role": "user", "content": f"q{i}"},
                    ],
                    "max_tokens": 4, "stream": True,
                }),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            ttft = None
            while True:
                line = r.readline()
                if not line or b"[DONE]" in line:
                    break
                if line.startswith(b"data:") and ttft is None:
                    ttft = time.perf_counter() - t0
            conn.close()
            out[i] = ttft

        warm: dict = {}
        one(0, warm)  # publishes the shared prefix; not timed
        outs: dict = {}
        ths = [
            threading.Thread(
                target=one, args=(i, outs), daemon=True,
                name=f"dllama-bench-fanout-{i}",
            )
            for i in range(1, fanout_n + 1)
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        vals = sorted(v * 1000 for v in outs.values() if v is not None)
        return round(vals[len(vals) // 2], 2) if vals else None

    fan_t0 = time.time()
    pre_fan = scrape_metrics()
    ttft_on = fanout_round(port)
    post_fan = scrape_metrics()
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", "/v1/debug/kv")
    kv_dbg = json.loads(c.getresponse().read().decode("utf-8"))
    c.close()

    metrics_text = scrape_metrics()

    # windowed SLO attainment/goodput over the load just served (ISSUE 7)
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", "/v1/debug/slo")
    slo_snap = json.loads(c.getresponse().read().decode("utf-8"))
    c.close()
    slo_5m = slo_snap["windows"]["5m"]
    slo = {
        "targets": slo_snap["targets"],
        "n_requests_5m": slo_5m["n_requests"],
        "attainment_5m": slo_5m["attainment"],
        "ttft_attainment_5m": slo_5m["ttft_attainment"],
        "goodput_tokens_per_s_5m": slo_5m["goodput_tokens_per_s"],
        "throughput_tokens_per_s_5m": slo_5m["throughput_tokens_per_s"],
    }

    # span-timeline export: the Perfetto file must be valid JSON with
    # spans from every serving component (ISSUE 7 acceptance)
    timeline_path = os.path.join(d, "timeline.json")
    srv.state.spans.export_file(timeline_path)
    with open(timeline_path) as f:
        tl = json.load(f)
    pid_names = {
        ev["pid"]: ev["args"]["name"]
        for ev in tl["traceEvents"]
        if ev.get("ph") == "M" and ev.get("name") == "process_name"
    }
    tl_counts: dict = {}
    for ev in tl["traceEvents"]:
        if ev.get("ph") == "X":
            comp = pid_names.get(ev["pid"], "?")
            tl_counts[comp] = tl_counts.get(comp, 0) + 1
    # per-request millisecond accounting for one traced request: the
    # coverage fraction is the ">=95% of wall time is spanned" bar
    tl_reqs = [r for r in read_jsonl(trace_path) if r.get("request_id")]
    summary = (
        srv.state.spans.request_summary(tl_reqs[-1]["request_id"])
        if tl_reqs else {}
    )
    timeline = {
        "n_spans": tl["dllama"]["n_spans"],
        "dropped": tl["dllama"]["dropped"],
        "spans_by_component": dict(sorted(tl_counts.items())),
        "request_coverage": summary.get("coverage"),
    }

    # in-process time-series store (ISSUE 9): force one sampler tick so
    # short runs have data regardless of wall-clock alignment, then read
    # the store the way the dashboard does
    srv.state.sampler.sample_once()
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", "/v1/debug/series")
    series_idx = json.loads(c.getresponse().read().decode("utf-8"))
    c.request(
        "GET", "/v1/debug/series?name=dllama_lanes_active&window=600"
    )
    series_lanes = json.loads(c.getresponse().read().decode("utf-8"))
    c.close()
    series = {
        "n_series": len(series_idx.get("names", [])),
        "interval_s": series_idx.get("interval_s"),
        "retention_s": series_idx.get("retention_s"),
        "lanes_active_points": len(series_lanes.get("points", [])),
        "anomaly_degraded": series_idx.get("anomaly", {}).get("degraded"),
        "anomaly_active": sorted(
            series_idx.get("anomaly", {}).get("active", {})
        ),
    }
    srv.shutdown()

    # sharing-off baseline: fresh engine + server with the pool disabled
    # (a second server so the on-run's radix state cannot leak in)
    engine_off = InferenceEngine(
        model_path, tokenizer=tok, batch_size=n_lanes, temperature=0.0
    )
    srv_off = serve(
        engine_off, tok, host="127.0.0.1", port=0, admission_chunk=32,
        kv_page_size=-1,
    )
    port_off = srv_off.server_address[1]
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_off.shutdown() below; no handle needed
        target=srv_off.serve_forever, daemon=True, name="dllama-bench-http-off"
    ).start()
    ttft_off = fanout_round(port_off)
    srv_off.shutdown()

    # model-free speculation (ISSUE 10): a repetitive JSON workload on a
    # spec-on server vs an identical spec-off server. Greedy streams are
    # token-exact either way (same bytes, same SSE event count), so the
    # comparison is pure timing: accepted draft runs amortize one weight
    # pass over several tokens and decode tok/s must beat the baseline
    # even on CPU smoke. Pool off on both so prefix sharing can't skew
    # the per-request timing.
    # short enough that the prompt leaves decode room inside seq_len;
    # the greedy continuation settles into a cycle the n-gram drafter
    # locks onto (acceptance climbs to full-k within a few verifies)
    spec_prompt = (
        'Repeat this list forever: {"name": "a", "value": 1}, '
        '{"name": "b", "value": 2}'
    )

    def decode_tok_s(srv_, n_rounds: int = 4) -> float:
        """Median completion tok/s over the warm rounds: completion
        tokens (from the scheduler's own finish records, not SSE event
        counts — burst flushes coalesce deltas) divided by the full
        request wall.  Round 0 pays any residual compiles and is
        discarded; prefill cost is identical on both servers so the
        on/off ratio isolates the decode path."""
        port_ = srv_.server_address[1]
        rates = []
        for rnd in range(n_rounds):
            seen = len(srv_.state.recorder.events(kind="finish"))
            conn = http.client.HTTPConnection(
                "127.0.0.1", port_, timeout=300
            )
            t0_ = time.perf_counter()
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "user", "content": spec_prompt}
                    ],
                    "max_tokens": 96, "stream": True, "temperature": 0.0,
                }),
                {"Content-Type": "application/json"},
            )
            for _line in conn.getresponse():
                pass
            wall = time.perf_counter() - t0_
            conn.close()
            ntok = sum(
                f["n_completion"]
                for f in srv_.state.recorder.events(kind="finish")[seen:]
            )
            if rnd > 0 and ntok > 0 and wall > 0:
                rates.append(ntok / wall)
        return sorted(rates)[len(rates) // 2] if rates else 0.0

    def scrape_port(port_: int) -> str:
        c = http.client.HTTPConnection("127.0.0.1", port_, timeout=30)
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode("utf-8")
        c.close()
        return text

    engine_spec_off = InferenceEngine(
        model_path, tokenizer=tok, batch_size=n_lanes, temperature=0.0
    )
    srv_spec_off = serve(
        engine_spec_off, tok, host="127.0.0.1", port=0, admission_chunk=32,
        kv_page_size=-1, speculation="off",
    )
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_spec_off.shutdown() below; no handle needed
        target=srv_spec_off.serve_forever, daemon=True,
        name="dllama-bench-http-spec-off",
    ).start()
    tok_s_off = decode_tok_s(srv_spec_off)
    srv_spec_off.shutdown()

    engine_spec = InferenceEngine(
        model_path, tokenizer=tok, batch_size=n_lanes, temperature=0.0
    )
    srv_spec = serve(
        engine_spec, tok, host="127.0.0.1", port=0, admission_chunk=32,
        kv_page_size=-1, speculation="ngram", spec_k=8,
    )
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_spec.shutdown() below; no handle needed
        target=srv_spec.serve_forever, daemon=True,
        name="dllama-bench-http-spec-on",
    ).start()
    # registry is process-global: delta the spec counters against a
    # snapshot taken before this server serves anything
    pre_spec = scrape_port(srv_spec.server_address[1])
    tok_s_on = decode_tok_s(srv_spec)
    post_spec = scrape_port(srv_spec.server_address[1])
    srv_spec.shutdown()
    spec_drafted = (
        metric_value(post_spec, "dllama_spec_draft_tokens_total")
        - metric_value(pre_spec, "dllama_spec_draft_tokens_total")
    )
    spec_accepted = (
        metric_value(post_spec, "dllama_spec_accepted_tokens_total")
        - metric_value(pre_spec, "dllama_spec_accepted_tokens_total")
    )
    spec_hist = re.search(
        r"^dllama_spec_accept_length_count (\d+)", post_spec, re.M
    )
    speculation = {
        "acceptance_rate": round(
            spec_accepted / spec_drafted if spec_drafted else 0.0, 3
        ),
        "draft_tokens": int(spec_drafted),
        "accepted_tokens": int(spec_accepted),
        "accept_len_hist_count": int(spec_hist.group(1)) if spec_hist else 0,
        "tok_s_spec_on": round(tok_s_on, 2),
        "tok_s_spec_off": round(tok_s_off, 2),
        "speedup_vs_off": round(
            tok_s_on / tok_s_off if tok_s_off else 0.0, 3
        ),
    }

    # second-generation speculation (ISSUE 18): a NATURAL-LANGUAGE
    # workload — no repeating cycle for the private n-gram index to lock
    # onto — driven as a seeded fanout: one prime request populates the
    # radix tree, then identical greedy requests replay sequentially, so
    # under --speculation shared each stream anchors on the primed
    # prefix and drafts from the previous stream's published
    # continuation. Private n-gram acceptance stays low on this text;
    # the shared store replays the sibling's exact accepted run, so its
    # acceptance must come out strictly higher (the CI gate) and the
    # amortized weight passes must beat the spec-off wall clock. The
    # draft round reuses the tiny target checkpoint as its own resident
    # draft model — a smoke of the draft_prefill/draft_step path, not a
    # perf claim (a same-size draft pays target price per draft token)
    # — and sends a NOVEL prompt per request: with nothing for either
    # n-gram source to replay, the first verify rejects the prompt-echo
    # draft and the cooldown re-routes the lane to the resident model.
    # byte-level tokenizer + llama3-shaped template ≈ chars + 91 prompt
    # tokens; keep well inside the serving model's seq_len 256 with
    # decode room for the 48-token completions below
    nl_prompt = (
        "Explain how a server reuses shared prefix attention state "
        "across requests to cut time to first token"
    )
    nl_novel = [
        "Describe how a radix tree over prompt tokens lets two "
        "requests share one cached prefix copy",
        "Compare continuous batching with static batching for large "
        "language model serving throughput",
        "Summarize why paged key value memory reduces fragmentation "
        "under many concurrent decode streams",
        "Outline how speculative decoding verifies a cheap draft with "
        "one batched target forward pass",
        "Explain why tensor parallel all reduce cost grows with the "
        "device count during token generation",
    ]

    def nl_round(mode: str, draft: str | None = None) -> dict:
        eng_ = InferenceEngine(
            model_path, tokenizer=tok, batch_size=n_lanes,
            temperature=0.0,
        )
        srv_ = serve(
            eng_, tok, host="127.0.0.1", port=0, admission_chunk=32,
            kv_page_size=16, speculation=mode, spec_k=8,
            draft_model=draft,
        )
        port_ = srv_.server_address[1]
        threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_.shutdown() below; no handle needed
            target=srv_.serve_forever, daemon=True,
            name=f"dllama-bench-http-nl-{mode}",
        ).start()

        def one_request(prompt: str = nl_prompt) -> tuple[float, int]:
            seen = len(srv_.state.recorder.events(kind="finish"))
            conn = http.client.HTTPConnection(
                "127.0.0.1", port_, timeout=300
            )
            t0_ = time.perf_counter()
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "user", "content": prompt}
                    ],
                    "max_tokens": 48, "stream": True,
                    "temperature": 0.0,
                }),
                {"Content-Type": "application/json"},
            )
            for _line in conn.getresponse():
                pass
            wall_ = time.perf_counter() - t0_
            conn.close()
            ntok_ = sum(
                f["n_completion"]
                for f in srv_.state.recorder.events(kind="finish")[seen:]
            )
            return wall_, ntok_

        # mode 'draft' sends a fresh novel prompt per request (n-gram
        # starvation exercises the resident model); the other modes
        # replay one prompt as a fanout
        prompts_ = nl_novel if mode == "draft" else [nl_prompt] * 5
        # sources are counted over the FULL round: the model rescue
        # fires on the earliest requests — once the store holds one
        # run, the common template tail lets it bridge even novel
        # prompts, which is the ladder working, not the model failing
        pre0_ = scrape_port(port_)
        one_request(prompts_[0])  # prime: compiles + radix insert,
        # timing discarded
        # stream 2 establishes the anchor and PUBLISHES its run; under
        # 'shared' the store only pays off from stream 3 on, so the
        # measured window starts after one more discard
        one_request(prompts_[1])
        pre_ = scrape_port(port_)
        rates_ = []
        for p_ in prompts_[2:]:
            wall_, ntok_ = one_request(p_)
            if ntok_ > 0 and wall_ > 0:
                rates_.append(ntok_ / wall_)
        post_ = scrape_port(port_)
        srv_.shutdown()
        drafted_ = (
            metric_value(post_, "dllama_spec_draft_tokens_total")
            - metric_value(pre_, "dllama_spec_draft_tokens_total")
        )
        accepted_ = (
            metric_value(post_, "dllama_spec_accepted_tokens_total")
            - metric_value(pre_, "dllama_spec_accepted_tokens_total")
        )

        def source_delta(src: str) -> int:
            pat = (
                rf'^dllama_spec_source_total{{source="{src}"}} '
                r"([0-9.eE+-]+)$"
            )
            pre_m = re.search(pat, pre0_, re.M)
            post_m = re.search(pat, post_, re.M)
            return int(
                (float(post_m.group(1)) if post_m else 0.0)
                - (float(pre_m.group(1)) if pre_m else 0.0)
            )

        return {
            "tok_s": sorted(rates_)[len(rates_) // 2] if rates_ else 0.0,
            "acceptance": accepted_ / drafted_ if drafted_ else 0.0,
            "drafted": int(drafted_),
            "sources": {
                s: source_delta(s) for s in ("ngram", "shared", "draft")
            },
            "store_tokens": int(
                metric_value(post_, "dllama_spec_shared_store_tokens")
            ),
        }

    nl_off = nl_round("off")
    nl_ngram = nl_round("ngram")
    nl_shared = nl_round("shared")
    nl_draft = nl_round("draft", draft=model_path)
    speculation_nl = {
        "tok_s_off": round(nl_off["tok_s"], 2),
        "tok_s_ngram": round(nl_ngram["tok_s"], 2),
        "tok_s_shared": round(nl_shared["tok_s"], 2),
        "tok_s_draft": round(nl_draft["tok_s"], 2),
        "accept_ngram": round(nl_ngram["acceptance"], 3),
        "accept_shared": round(nl_shared["acceptance"], 3),
        "accept_draft": round(nl_draft["acceptance"], 3),
        "speedup_shared_vs_off": round(
            nl_shared["tok_s"] / nl_off["tok_s"]
            if nl_off["tok_s"] else 0.0, 3
        ),
        "shared_sources": nl_shared["sources"],
        "draft_sources": nl_draft["sources"],
        "shared_store_tokens": nl_shared["store_tokens"],
    }

    fan_recs = [
        r for r in read_jsonl(trace_path)
        if r.get("submitted_unix", 0) >= fan_t0
        and r.get("reused_prefix_tokens") and r.get("n_prompt_tokens")
    ]
    prefix_fanout = {
        "n_streams": fanout_n,
        "n_reused_streams": len(fan_recs),
        "shared_prefix_ratio": round(
            max(
                (r["reused_prefix_tokens"] / r["n_prompt_tokens"]
                 for r in fan_recs),
                default=0.0,
            ), 3,
        ),
        "reused_tokens_total": int(
            metric_value(post_fan, "dllama_reused_prefix_tokens_total")
            - metric_value(pre_fan, "dllama_reused_prefix_tokens_total")
        ),
        "radix_hits": int(
            metric_value(post_fan, "dllama_radix_hits_total")
            - metric_value(pre_fan, "dllama_radix_hits_total")
        ),
        "ttft_ms_p50_sharing_on": ttft_on,
        "ttft_ms_p50_sharing_off": ttft_off,
        "kv_pool": kv_dbg.get("pool"),
    }

    def hist_count(name: str) -> int:
        m = re.search(rf"^{name}_count (\d+)", metrics_text, re.M)
        return int(m.group(1)) if m else 0

    gaps_ms = sorted(
        (b - a) * 1000
        for a, b in zip(victim_arrivals, victim_arrivals[1:])
    )
    churn_chunks = (
        metric_value(metrics_text, "dllama_admission_chunks_total")
        - metric_value(pre_churn, "dllama_admission_chunks_total")
    )
    admission_churn = {
        "n_gaps": len(gaps_ms),
        "max_gap_ms": round(gaps_ms[-1], 2) if gaps_ms else None,
        "p99_gap_ms": (
            round(gaps_ms[min(len(gaps_ms) - 1,
                              int(0.99 * (len(gaps_ms) - 1)))], 2)
            if gaps_ms else None
        ),
        "chunks_per_admission": round(churn_chunks / 2, 1),
    }

    recs = [r for r in read_jsonl(trace_path) if r["ttft_s"] is not None]
    ttfts = sorted(r["ttft_s"] * 1000 for r in recs)
    waits = sorted(r["queue_wait_s"] * 1000 for r in recs)

    # instrumentation overhead: median decode-block wall time with the
    # registry + flight recorder + span tracker enabled vs ALL disabled
    # (same compiled program, same lanes) — the acceptance bar covers the
    # whole per-dispatch hook cost, not just the histogram observe
    from dllama_tpu.obs.recorder import get_recorder
    from dllama_tpu.obs.spans import get_span_tracker

    reg = get_registry()
    rec = get_recorder()
    spans_t = get_span_tracker()

    def median_block_s(k: int = 9) -> float:
        times = []
        for _ in range(k):
            t0 = time.perf_counter()
            engine.decode_lanes(
                [1] * n_lanes, [64] * n_lanes, 8,
                active=[True] * n_lanes,
            )
            times.append(time.perf_counter() - t0)
        return sorted(times)[k // 2]

    engine.decode_lanes(  # warm the compiled program
        [1] * n_lanes, [64] * n_lanes, 8, active=[True] * n_lanes
    )
    on_s = median_block_s()
    reg.disable()
    rec_was_enabled, rec.enabled = rec.enabled, False
    spans_were_enabled, spans_t.enabled = spans_t.enabled, False
    off_s = median_block_s()
    spans_t.enabled = spans_were_enabled
    rec.enabled = rec_was_enabled
    reg.enable()
    overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s > 0 else 0.0

    # self-healing under chaos (ISSUE 12): seeded fault rounds against a
    # fresh server — completion rate under a retryable transient schedule
    # (the CI gate holds it at 1.0 with every stream byte-identical to
    # the fault-free round), recovered-lane count and the p99 inter-delta
    # gap through a poison recovery vs fault-free, and the shed counter
    # under queue pressure. docs/resilience.md is the map.
    from dllama_tpu.runtime.faults import set_fault_plane

    engine_res = InferenceEngine(
        model_path, tokenizer=tok, batch_size=n_lanes, temperature=0.0
    )
    srv_res = serve(
        engine_res, tok, host="127.0.0.1", port=0, admission_chunk=32,
    )
    port_res = srv_res.server_address[1]
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_res.shutdown() below; no handle needed
        target=srv_res.serve_forever, daemon=True,
        name="dllama-bench-http-res",
    ).start()
    res_prompts = [f"resilience workload item {i}" for i in range(6)]

    def res_round() -> tuple[dict, int]:
        """One concurrent round: ({index: content} for completed
        requests, count of structured-retryable failures)."""
        results: dict = {}

        def one(i: int) -> None:
            conn = http.client.HTTPConnection(
                "127.0.0.1", port_res, timeout=300
            )
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "user", "content": res_prompts[i]}
                    ],
                    "max_tokens": 12, "temperature": 0.0,
                }),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            results[i] = (r.status, json.loads(r.read().decode("utf-8")))
            conn.close()

        ths = [
            threading.Thread(
                target=one, args=(i,), daemon=True,
                name=f"dllama-bench-res-{i}",
            )
            for i in range(len(res_prompts))
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        contents, n_retryable = {}, 0
        for i, (status, body) in results.items():
            if status == 200:
                contents[i] = body["choices"][0]["message"]["content"]
            elif body.get("error", {}).get("retryable"):
                n_retryable += 1
        return contents, n_retryable

    res_round()                    # warm: compiles + first publishes
    res_baseline, _ = res_round()  # fault-free reference bytes

    plane = set_fault_plane("dispatch:p=0.05:seed=7")
    res_faulted, _ = res_round()
    transient_injected = plane.counts().get("dispatch", 0)
    set_fault_plane("")
    byte_identical = sum(
        1 for i, c in res_faulted.items() if res_baseline.get(i) == c
    )

    # poison recovery: a victim stream measures its inter-delta gaps
    # while a mid-stream decode poison forces its lane through the
    # re-prefill resume path; the same stream fault-free is the baseline
    def victim_gaps(spec: str | None) -> list[float]:
        arrivals: list[float] = []
        conn = http.client.HTTPConnection("127.0.0.1", port_res, timeout=300)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "chaos victim"}],
                "max_tokens": 48, "stream": True, "temperature": 0.0,
            }),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        while True:
            line = r.readline()
            if not line or b"[DONE]" in line:
                break
            if line.startswith(b"data:"):
                arrivals.append(time.perf_counter())
                if spec is not None and len(arrivals) == 1:
                    set_fault_plane(spec)  # decode is in flight: arm now
                    spec = None
        conn.close()
        return [(b - a) * 1000 for a, b in zip(arrivals, arrivals[1:])]

    def gap_p99(gaps: list[float]) -> float | None:
        if not gaps:
            return None
        g = sorted(gaps)
        return round(g[min(len(g) - 1, int(0.99 * (len(g) - 1)))], 2)

    gaps_base = victim_gaps(None)
    pre_res = scrape_port(port_res)
    gaps_poison = victim_gaps("dispatch:op=decode_lanes:nth=2:kind=poison")
    set_fault_plane("")
    post_res = scrape_port(port_res)
    recovered = int(
        metric_value(post_res, "dllama_lanes_recovered_total")
        - metric_value(pre_res, "dllama_lanes_recovered_total")
    )

    # load shedding: a sentinel parked in the idle scheduler's queue
    # (appended WITHOUT a cv notify, so the waiting loop never pops it)
    # trips the depth gate deterministically
    st_res = srv_res.state
    sched_res = st_res.scheduler
    st_res.max_queue_depth = 1
    sentinel = object()
    with sched_res.cv:
        sched_res.pending.append(sentinel)
    n_shed = 0
    for _ in range(2):
        conn = http.client.HTTPConnection("127.0.0.1", port_res, timeout=30)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "shed me"}],
                "max_tokens": 4,
            }),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        r.read()
        if r.status == 429:
            n_shed += 1
        conn.close()
    with sched_res.cv:
        sched_res.pending.remove(sentinel)
    st_res.max_queue_depth = 0
    srv_res.shutdown()

    resilience = {
        "n_requests": len(res_prompts),
        "completion_rate_transient": round(
            len(res_faulted) / len(res_prompts), 3
        ),
        "byte_identical_transient": byte_identical,
        "faults_injected_transient": int(transient_injected),
        "recovered_lanes": recovered,
        "p99_gap_ms_baseline": gap_p99(gaps_base),
        "p99_gap_ms_recovery": gap_p99(gaps_poison),
        "requests_shed": n_shed,
    }

    # oversubscription (ISSUE 16): 2 decode lanes serving 4 concurrent
    # streams via park/resume through the pool-native paged-KV path; the
    # slab paged server running the identical workload is the baseline
    # for TPOT and for KV copy traffic (slab moves bytes on every
    # adopt/publish, pool-native only on COW boundary forks)
    def over_round(port_, n_streams, max_tokens=40):
        """n_streams concurrent greedy streams: (n completed, per-stream
        TPOT ms from SSE arrival deltas)."""
        tpots: list = [None] * n_streams
        done = [False] * n_streams

        def one(i: int) -> None:
            arrivals: list[float] = []
            conn = http.client.HTTPConnection(
                "127.0.0.1", port_, timeout=300
            )
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "user",
                         "content": f"oversubscribed stream {i}"}
                    ],
                    "max_tokens": max_tokens, "stream": True,
                    "temperature": 0.0,
                }),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            while True:
                line = r.readline()
                if not line or b"[DONE]" in line:
                    break
                if line.startswith(b"data:"):
                    arrivals.append(time.perf_counter())
            conn.close()
            done[i] = bool(arrivals)
            if len(arrivals) > 1:
                tpots[i] = (
                    (arrivals[-1] - arrivals[0]) / (len(arrivals) - 1) * 1000
                )

        ths = [
            threading.Thread(
                target=one, args=(i,), daemon=True,
                name=f"dllama-bench-over-{i}",
            )
            for i in range(n_streams)
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        return sum(done), sorted(t for t in tpots if t is not None)

    def over_server(native: bool):
        eng = InferenceEngine(
            model_path, tokenizer=tok, batch_size=2, temperature=0.0
        )
        srv_ = serve(
            eng, tok, host="127.0.0.1", port=0, admission_chunk=32,
            kv_page_size=4, kv_native=native, max_streams=4,
        )
        threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_.shutdown() below; no handle needed
            target=srv_.serve_forever, daemon=True,
            name=f"dllama-bench-http-over-{'native' if native else 'slab'}",
        ).start()
        port_ = srv_.server_address[1]
        over_round(port_, 2, max_tokens=8)  # warm: compiles + publishes
        pre = scrape_port(port_)
        n_done, tpots_ = over_round(port_, 4)
        post = scrape_port(port_)
        srv_.shutdown()
        return n_done, tpots_, pre, post

    over_done, over_tpots, pre_over, post_over = over_server(native=True)
    slab_done, slab_tpots, pre_slab, post_slab = over_server(native=False)

    def p50(xs):
        return round(xs[len(xs) // 2], 2) if xs else None

    oversubscription = {
        "streams": 4,
        "lanes": 2,
        "completed": int(over_done),
        "stream_resumes": int(
            metric_value(post_over, "dllama_stream_resumes_total")
            - metric_value(pre_over, "dllama_stream_resumes_total")
        ),
        "tpot_ms_p50": p50(over_tpots),
        "tpot_ms_p50_slab": p50(slab_tpots),
        "completed_slab": int(slab_done),
        "kv_copy_bytes_native": int(
            metric_value(post_over, "dllama_kv_copy_bytes_total")
            - metric_value(pre_over, "dllama_kv_copy_bytes_total")
        ),
        "kv_copy_bytes_slab": int(
            metric_value(post_slab, "dllama_kv_copy_bytes_total")
            - metric_value(pre_slab, "dllama_kv_copy_bytes_total")
        ),
    }

    # predictive admission under overload (ISSUE 20): the same 4x
    # sustained-overload wave — mixed priorities, half the requests
    # carrying a deadline the machine can honor and half a TTFT budget
    # it provably cannot — against a predictive-on server and a
    # queue-depth-only baseline. The baseline admits everything and
    # burns lane time generating tokens for requests that already blew
    # their budget; the predictor rejects those up front (429 +
    # predicted Retry-After) so the same lanes finish the feasible work
    # sooner. Goodput counts ONLY tokens from requests that met their
    # own deadline, so wasted capacity shows up as the gap.
    def overload_server(predict: bool):
        eng = InferenceEngine(
            model_path, tokenizer=tok, batch_size=2, temperature=0.0
        )
        srv_ = serve(
            eng, tok, host="127.0.0.1", port=0, admission_chunk=32,
            slo_ttft_ms=600000.0, slo_tpot_ms=60000.0,
            admission_predict=predict,
        )
        threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at srv_.shutdown() below; no handle needed
            target=srv_.serve_forever, daemon=True,
            name=f"dllama-bench-http-ovl-{'pred' if predict else 'base'}",
        ).start()
        return srv_

    def overload_round(srv_) -> dict:
        port_ = srv_.server_address[1]
        # warm: compile prefill/decode so both configs time steady state
        ovl_warm = http.client.HTTPConnection("127.0.0.1", port_, timeout=300)
        ovl_warm.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "warm"}],
                "max_tokens": 4, "temperature": 0.0,
            }),
            {"Content-Type": "application/json"},
        )
        ovl_warm.getresponse().read()
        ovl_warm.close()
        pre = scrape_port(port_)
        outs: dict = {}

        def one(i: int) -> None:
            feasible = i % 2 == 0
            req = {
                "messages": [
                    {"role": "user", "content": f"overload stream {i}"}
                ],
                "max_tokens": 24, "temperature": 0.0,
                "priority": ("high", "normal", "low")[i % 3],
            }
            if feasible:
                req["deadline_ms"] = 300000.0
            else:
                req["ttft_budget_ms"] = 1.0  # unmeetable: < one chunk
            conn = http.client.HTTPConnection("127.0.0.1", port_, timeout=300)
            t0_ = time.perf_counter()
            conn.request(
                "POST", "/v1/chat/completions", json.dumps(req),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            data = json.loads(r.read().decode("utf-8"))
            wall_ = time.perf_counter() - t0_
            conn.close()
            n_tok = (
                data.get("usage", {}).get("completion_tokens", 0)
                if r.status == 200 else 0
            )
            outs[i] = (r.status, feasible, n_tok, wall_)

        n_over = 16  # 8 concurrent per wave on 2 lanes = 4x overload
        t0_ = time.perf_counter()
        for wave in range(2):
            ths = [
                threading.Thread(
                    target=one, args=(wave * 8 + j,), daemon=True,
                    name=f"dllama-bench-ovl-{wave * 8 + j}",
                )
                for j in range(8)
            ]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
        wall = time.perf_counter() - t0_
        post = scrape_port(port_)

        def labeled_delta(name: str, labels: str) -> int:
            pat = rf"^{re.escape(name + labels)} ([0-9.eE+-]+)$"
            pre_m = re.search(pat, pre, re.M)
            post_m = re.search(pat, post, re.M)
            return int(
                (float(post_m.group(1)) if post_m else 0.0)
                - (float(pre_m.group(1)) if pre_m else 0.0)
            )

        # a request's tokens are goodput only if it met its OWN deadline:
        # the tight-budget half can never meet 1 ms TTFT, so its tokens
        # are pure waste wherever they were generated
        good_tokens = sum(n for st, feas, n, w in outs.values()
                          if st == 200 and feas)
        c_adm = http.client.HTTPConnection("127.0.0.1", port_, timeout=30)
        c_adm.request("GET", "/v1/debug/admission")
        adm = json.loads(c_adm.getresponse().read().decode("utf-8"))
        c_adm.close()
        return {
            "n_requests": n_over,
            "completed": sum(1 for st, _, _, _ in outs.values() if st == 200),
            "rejected": sum(1 for st, _, _, _ in outs.values() if st != 200),
            "goodput_tok_s": round(good_tokens / wall, 2),
            "wall_s": round(wall, 3),
            "shed_by_reason": {
                "infeasible": labeled_delta(
                    "dllama_admission_rejected_total",
                    '{reason="infeasible"}',
                ),
                "queue_full": labeled_delta(
                    "dllama_requests_shed_total", '{reason="queue_full"}'
                ),
            },
            "prediction_error_ms": adm.get("prediction_error"),
        }

    srv_pred = overload_server(predict=True)
    ovl_pred = overload_round(srv_pred)
    srv_pred.shutdown()
    srv_base = overload_server(predict=False)
    ovl_base = overload_round(srv_base)
    srv_base.shutdown()
    overload = {
        "overload_factor": 4,
        "predictive": ovl_pred,
        "baseline": ovl_base,
        "goodput_tok_s": ovl_pred["goodput_tok_s"],
        "goodput_tok_s_baseline": ovl_base["goodput_tok_s"],
    }
    # CI gates (ISSUE 20 acceptance): predictive goodput must not lose
    # to the queue-depth-only baseline on the same overload wave, every
    # infeasible request must be refused before admission, and the
    # predictor must be scoring itself with finite error percentiles
    assert ovl_pred["goodput_tok_s"] >= ovl_base["goodput_tok_s"], (
        f"predictive goodput {ovl_pred['goodput_tok_s']} < baseline "
        f"{ovl_base['goodput_tok_s']}"
    )
    assert ovl_pred["shed_by_reason"]["infeasible"] == 8, overload
    perr = ovl_pred["prediction_error_ms"] or {}
    assert (
        perr.get("p50_ms") is not None
        and math.isfinite(perr["p50_ms"])
        and perr.get("p95_ms") is not None
        and math.isfinite(perr["p95_ms"])
    ), overload

    # replica fleet (ISSUE 17): 2-replica in-process topology behind the
    # prefix-affinity router. Three rounds on a shared-prefix workload:
    # random routing vs affinity routing (each round uses its OWN shared
    # prefix so neither inherits the other's radix warmth — the prefix
    # hit-rate gap is the routing policy, not cache history), then a
    # seeded replica-kill round where every stream must still complete
    # through mid-stream failover. The obs registry is process-global so
    # both routers share metric families; every number is a pre/post
    # delta around its own round.
    from dllama_tpu.fleet.launch import launch_inprocess_fleet
    from dllama_tpu.fleet.router import serve_router

    fleet_h = launch_inprocess_fleet(
        model_path, tok_path, n_replicas=2, batch_size=2,
    )
    rand_srv = serve_router(
        fleet_h.registry, Tokenizer(tok_path), host="127.0.0.1", port=0,
        routing="random", stall_timeout_s=30.0, start_poller=False,
    )
    threading.Thread(  # dlint: disable=thread-hygiene — serve_forever exits at rand_srv.shutdown() below; no handle needed
        target=rand_srv.serve_forever, daemon=True,
        name="dllama-bench-fleet-random",
    ).start()
    fleet_port = fleet_h.router.server_address[1]
    rand_port = rand_srv.server_address[1]
    fleet_n = 6

    def fleet_round(port_: int, tag: str) -> dict:
        """1 warmup + fleet_n concurrent unary requests sharing a long
        system prompt unique to this round; returns goodput + hit deltas."""
        # byte-level tokenizer: keep prompt + template well under the
        # tiny model's seq_len 256
        sysmsg = f"Shared fleet preamble for round {tag}. " * 2

        def one(i: int, out: dict) -> None:
            conn = http.client.HTTPConnection("127.0.0.1", port_, timeout=300)
            conn.request(
                "POST", "/v1/chat/completions",
                json.dumps({
                    "messages": [
                        {"role": "system", "content": sysmsg},
                        {"role": "user", "content": f"fleet q{i}"},
                    ],
                    "max_tokens": 8, "temperature": 0.0,
                }),
                {"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            body = json.loads(r.read().decode("utf-8"))
            if r.status == 200:
                out[i] = body["usage"]["completion_tokens"]
            conn.close()

        one(0, {})  # warmup publishes this round's prefix on its replica
        pre = scrape_port(port_)
        t0_ = time.perf_counter()
        outs: dict = {}
        ths = [
            threading.Thread(
                target=one, args=(i, outs), daemon=True,
                name=f"dllama-bench-fleet-{tag}-{i}",
            )
            for i in range(1, fleet_n + 1)
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.perf_counter() - t0_
        post = scrape_port(port_)

        def delta(name: str) -> float:
            return metric_value(post, name) - metric_value(pre, name)

        return {
            "completed": len(outs),
            "goodput_tok_s": round(sum(outs.values()) / wall, 2),
            "affinity_hit_rate": round(
                delta("dllama_router_affinity_hits_total") / fleet_n, 3
            ),
            "prefix_cache_hits": int(delta("dllama_prefix_cache_hits_total")),
        }

    fleet_random = fleet_round(rand_port, "rand")
    fleet_affinity = fleet_round(fleet_port, "aff")
    rand_srv.shutdown()

    # seeded kill round: 4 greedy streams while the fault plane drops one
    # stream mid-flush on each replica — the router must resume each dead
    # stream on the sibling and the client side must still read a
    # finish_reason (completion rate 1.0; byte-identity is asserted in
    # tests/test_fleet.py where the baseline bytes are captured)
    kill_done = [False] * 4

    def kill_stream(i: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", fleet_port, timeout=300)
        conn.request(
            "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": f"kill round {i}"}],
                "max_tokens": 12, "stream": True, "temperature": 0.0,
            }),
            {"Content-Type": "application/json"},
        )
        r = conn.getresponse()
        raw = r.read().decode("utf-8")
        conn.close()
        kill_done[i] = (
            '"finish_reason": "' in raw or '"finish_reason":"' in raw
        )

    fr_state = fleet_h.router.state
    # arm ONE fleet-wide one-shot kill (2nd SSE flush, any replica)
    # rather than pre-computing a victim: the router's capacity-aware
    # spill can steer a burst away from any one replica between arming
    # and streaming, and arming both replicas separately lets a single
    # unlucky stream eat both faults (die, fail over, die again) and
    # exhaust its two candidates. One op-less schedule counts draws
    # across the whole fleet, so exactly one stream dies wherever it
    # landed and its sibling is guaranteed clean for the catch-up.
    pre_kill = scrape_port(fleet_port)
    set_fault_plane("sse_flush:nth=2:n=1")
    kill_threads = [
        threading.Thread(
            target=kill_stream, args=(i,), daemon=True,
            name=f"dllama-bench-fleet-kill-{i}",
        )
        for i in range(4)
    ]
    for t in kill_threads:
        t.start()
    for t in kill_threads:
        t.join()
    set_fault_plane("")
    post_kill = scrape_port(fleet_port)

    # fleet observability plane (ISSUE 19): the kill round left a
    # stitched story behind — pull the failed-over request's merged
    # Perfetto timeline through the router, plus the fleet aggregates
    # and the anomaly monitor's verdict. A healthy run must report the
    # monitor calm (anomaly_degraded False); failover_gap_ms_p99 is the
    # cost of a mid-stream hand-off as the client saw it.
    def fleet_json(path_: str) -> dict:
        conn = http.client.HTTPConnection("127.0.0.1", fleet_port, timeout=60)
        conn.request("GET", path_)
        r = conn.getresponse()
        body = json.loads(r.read().decode("utf-8"))
        conn.close()
        return body

    scrape_ok = fr_state.fleet.scrape_once()
    stitched: dict = {}
    recent = fleet_json("/v1/fleet/timeline").get("recent", [])
    hop = next((e for e in recent if e.get("n_failovers")), None)
    if hop is not None:
        merged = fleet_json(
            f"/v1/fleet/timeline?request_id={hop['request_id']}"
        )
        info = merged.get("dllama", {})
        sources = info.get("sources", {})
        stitched = {
            "replicas": info.get("replicas", []),
            "n_spans": info.get("n_spans", 0),
            "router_spans": sources.get("router", 0),
            "replica_spans": sum(
                n for k, n in sources.items() if k != "router"
            ),
            "fetch_errors": len(info.get("fetch_errors", [])),
        }
    monitor = fr_state.fleet.monitor.status()
    gap_p99 = fr_state.m_gap.percentile(0.99)
    fleet_obs = {
        "scrape_ok": all(scrape_ok.values()) and len(scrape_ok) == 2,
        "fleet_goodput_series": (
            "dllama_fleet_goodput_tokens_per_s" in fr_state.fleet.store.names()
        ),
        "anomaly_degraded": bool(monitor["degraded"]),
        "active_signals": sorted(monitor.get("active", {})),
        "failover_gap_ms_p99": (
            round(gap_p99 * 1000, 2) if gap_p99 is not None else None
        ),
        "stitched": stitched,
    }

    fleet_block = {
        "n_replicas": 2,
        "n_requests": fleet_n,
        "goodput_tok_s": fleet_affinity["goodput_tok_s"],
        "affinity": fleet_affinity,
        "random": fleet_random,
        "kill": {
            "n_streams": len(kill_done),
            "completed": sum(kill_done),
            "completion_rate": round(sum(kill_done) / len(kill_done), 3),
            "failovers": int(
                metric_value(post_kill, "dllama_router_failovers_total")
                - metric_value(pre_kill, "dllama_router_failovers_total")
            ),
        },
        "fleet_obs": fleet_obs,
    }
    fleet_h.close()

    return {
        "n_clients": n_clients,
        "n_traced": len(recs),
        "ttft_ms_p50": round(ttfts[len(ttfts) // 2], 2) if ttfts else None,
        "queue_wait_ms_p50": (
            round(waits[len(waits) // 2], 3) if waits else None
        ),
        "ttft_hist_count": hist_count("dllama_ttft_seconds"),
        "tpot_hist_count": hist_count("dllama_tpot_seconds"),
        "admission_churn": admission_churn,
        "admission_chunks_total": int(
            metric_value(metrics_text, "dllama_admission_chunks_total")
        ),
        "decode_stall_count": hist_count("dllama_decode_stall_seconds"),
        "decode_stall_sum_s": round(
            metric_value(metrics_text, "dllama_decode_stall_seconds_sum"), 4
        ),
        "prefix_fanout": prefix_fanout,
        "speculation": speculation,
        "speculation_nl": speculation_nl,
        "resilience": resilience,
        "oversubscription": oversubscription,
        "overload": overload,
        "fleet": fleet_block,
        "slo": slo,
        "timeline": timeline,
        "series": series,
        "obs_overhead_pct": round(overhead_pct, 2),
    }


_partial_result: dict = {}
_wall_timer = None


def _arm_wall_watchdog() -> None:
    """If the run wedges mid-measurement (the tunnel can drop between the
    probe and the final readback), emit the best record gathered so far
    and exit instead of hanging the driver indefinitely. Armed AFTER the
    probe-retry phase so retry time doesn't eat the measurement budget;
    cancelled before the final print so a healthy run emits exactly one
    JSON line."""
    import threading

    global _wall_timer
    wall_s = float(os.environ.get("BENCH_WALL_TIMEOUT_S", "2700"))

    def fire():
        rec = dict(_partial_result) or {
            "metric": "bench_error",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": None,
            "comparable": False,
        }
        rec["error"] = f"wall watchdog fired after {wall_s:.0f}s (tunnel wedge mid-run)"
        print(json.dumps(rec), flush=True)
        write_bench_summaries(rec)  # partial trajectory beats no trajectory
        os._exit(0 if _partial_result else 1)

    _wall_timer = threading.Timer(wall_s, fire)
    _wall_timer.daemon = True
    _wall_timer.start()


def _device_watchdog(timeout_s: float = 180.0) -> None:
    """In-process confirmation that the platform answers (the tunneled TPU
    HANGS rather than erroring when its relay is down); falls back to CPU
    re-exec on failure."""
    import threading

    done = threading.Event()
    result: dict = {}

    def probe():
        try:
            import numpy as _np

            import jax.numpy as _jnp

            _ = _np.asarray(_jnp.ones((8, 8)) @ _jnp.ones((8, 8)))
            result["ok"] = True
        except Exception as e:  # real error: report it, don't fake a timeout
            result["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    t = threading.Thread(  # dlint: disable=thread-hygiene — a wedged device probe can block forever; the daemon thread is deliberately abandoned after done.wait times out
        target=probe, daemon=True, name="dllama-device-probe"
    )
    t.start()
    done.wait(timeout_s)
    if not result.get("ok"):
        _cpu_fallback_reexec(result.get("error", "device probe timed out"))


def main() -> None:
    # platform/cache side effects live here, not at module level, so that
    # importing bench (tests use headline_record) stays side-effect free
    from dllama_tpu.parallel.mesh import (
        enable_compilation_cache,
        reassert_platform,
    )

    reassert_platform()
    enable_compilation_cache()

    from jax.sharding import NamedSharding, PartitionSpec as P

    from dllama_tpu.models import forward, init_kv_cache
    from dllama_tpu.models.synthetic import make_header, random_params
    from dllama_tpu.parallel import cache_specs, make_mesh

    if not os.environ.get("BENCH_CPU_FALLBACK") and _accelerator_expected():
        if not _tunnel_probe_retry():
            _cpu_fallback_reexec(
                "all subprocess probes failed/timed out over the retry window"
            )
        # probes just answered, so in-process init should be quick; the
        # generous timeout covers a slow first backend init, and the wall
        # watchdog bounds a post-probe wedge
        _device_watchdog(timeout_s=300.0)
    _arm_wall_watchdog()  # after the probe phase: retry time must not eat
    # the measurement budget

    preset = os.environ.get("BENCH_PRESET", "llama-8b")
    steps = int(os.environ.get("BENCH_STEPS", "64"))
    tp = int(os.environ.get("BENCH_TP", "0")) or 1
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "1024"))
    weight_format = os.environ.get("BENCH_FORMAT", "q40")
    kv = os.environ.get("BENCH_KV", "bf16")  # bf16 | int8 (QuantKV)
    if kv not in ("bf16", "int8"):
        raise SystemExit(f"BENCH_KV must be bf16 or int8, got {kv!r}")
    kv_dtype = jnp.int8 if kv == "int8" else jnp.bfloat16

    h = make_header(preset, max_seq_len=seq_len)
    log(f"bench: {preset}, tp={tp}, steps={steps}, seq_len={h.seq_len}, "
        f"format={weight_format}, kv={kv}, devices={jax.devices()}")

    mesh = make_mesh(tp=tp)
    t0 = time.perf_counter()
    params = random_params(
        h, dtype=jnp.bfloat16, mesh=mesh, weight_format=weight_format,
        # fused qkv/w13 launches, like the engine's q40 default
        fuse=tp if weight_format in ("q40", "q40i8", "q40i4") else 0,
    )
    cache = init_kv_cache(h, batch_size=1, dtype=kv_dtype)
    cspecs = cache_specs(h)
    cache = {
        k: jax.device_put(v, NamedSharding(mesh, cspecs[k])) for k, v in cache.items()
    }
    jax.block_until_ready(jax.tree.leaves(params)[0])
    log(f"params built in {time.perf_counter() - t0:.1f}s")

    from jax import lax

    # On-device multi-step decode (the engine's decode_block structure):
    # the sample->feed loop runs under fori_loop, one host dispatch per
    # block of `steps` tokens.
    @partial(jax.jit, donate_argnums=(2,), static_argnums=(3,))
    def decode_block(params, token, cache, n, pos0):
        # batch-generic (jit specializes per token/cache shape): the same
        # program serves the single-stream and the concurrent-lane metric
        def body(i, carry):
            tok, cache = carry
            logits, cache = forward(params, h, tok, pos0 + i, cache, mesh=mesh)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return nxt[:, None], cache
        return lax.fori_loop(0, n, body, (token, cache))

    token_sharding = NamedSharding(mesh, P(None, None))
    tok = jax.device_put(jnp.asarray([[1]], dtype=jnp.int32), token_sharding)

    # warmup / compile (np.asarray: full sync — block_until_ready returns
    # early on the tunneled axon platform)
    t0 = time.perf_counter()
    tok_out, cache = decode_block(params, tok, cache, steps, jnp.int32(0))
    _ = np.asarray(tok_out)
    log(f"compile+first block: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    tok_out, cache = decode_block(params, tok_out, cache, steps, jnp.int32(steps))
    # np.asarray (not block_until_ready): on the tunneled axon platform
    # block_until_ready returns before the remote computation finishes
    _ = np.asarray(tok_out)
    dt = time.perf_counter() - t0
    tok_s = steps / dt
    per_chip = tok_s / tp
    if weight_format == "q40i8":
        from dllama_tpu.ops.int8_matmul import pick_group

        w_bytes = weight_bytes_per_token(
            h, weight_format, i8_group=pick_group(h, tp)
        )
    else:
        w_bytes = weight_bytes_per_token(h, weight_format)
    weight_gbs = w_bytes * tok_s / tp / 1e9  # per-chip weight-read bandwidth
    log(f"{steps} decode steps in {dt:.2f}s -> {tok_s:.2f} tok/s "
        f"({per_chip:.2f}/chip, ~{weight_gbs:.0f} GB/s weight reads/chip)")
    # headline metric is banked the moment it exists: if a later section
    # (TTFT / lanes) wedges the tunnel, the wall watchdog emits this
    _partial_result.update(
        headline_record(
            preset,
            weight_format,
            kv,
            per_chip,
            weight_gbs,
            fallback=bool(os.environ.get("BENCH_CPU_FALLBACK")),
        )
    )

    # step-time percentiles: re-dispatch the SAME compiled block at later
    # cache positions until the sequence runs out (bounded extra work, no
    # new compiles — `steps` is the static arg). The headline single-block
    # number above stays untouched; these samples only feed the p50/p90
    # distribution in BENCH_DECODE.json.
    block_ms = [dt * 1000.0]
    pos = 2 * steps
    while pos + steps <= h.seq_len and len(block_ms) < 7:
        t0 = time.perf_counter()
        tok_out, cache = decode_block(
            params, tok_out, cache, steps, jnp.int32(pos)
        )
        _ = np.asarray(tok_out)
        block_ms.append((time.perf_counter() - t0) * 1000.0)
        pos += steps
    _partial_result["step_ms"] = {
        "block_tokens": steps,
        "n_blocks": len(block_ms),
        "p50": round(float(np.percentile(block_ms, 50)), 2),
        "p90": round(float(np.percentile(block_ms, 90)), 2),
        "max": round(float(max(block_ms)), 2),
        "per_token_p50": round(
            float(np.percentile(block_ms, 50)) / steps, 3
        ),
    }
    log(f"step ms over {len(block_ms)} blocks of {steps}: "
        f"p50 {_partial_result['step_ms']['p50']} "
        f"p90 {_partial_result['step_ms']['p90']}")

    # p50 TTFT: prefill a 128-token prompt + first greedy token, one
    # compiled program per shape (BASELINE.json names p50 TTFT as part of
    # the headline metric)
    ttft_p50 = None
    if not os.environ.get("BENCH_SKIP_TTFT"):
        prompt_len = min(128, h.seq_len // 2)

        @partial(jax.jit, donate_argnums=(2,))
        def prefill_first(params, tokens, cache, pos):
            logits, cache = forward(params, h, tokens, pos, cache, mesh=mesh)
            return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), cache

        prompt = jax.device_put(
            jnp.ones((1, prompt_len), jnp.int32), token_sharding
        )
        samples = []
        for i in range(5):
            t0 = time.perf_counter()
            first_tok, cache = prefill_first(params, prompt, cache, jnp.int32(0))
            _ = np.asarray(first_tok)
            samples.append((time.perf_counter() - t0) * 1000)
        ttft_p50 = float(np.median(samples[1:]))  # drop the compile run
        log(f"TTFT (prefill {prompt_len} + 1 token): p50 {ttft_p50:.1f} ms "
            f"(samples: {[f'{s:.0f}' for s in samples]})")

    # concurrent lanes: aggregate decode throughput with BENCH_BATCH
    # independent streams in one program (the continuous-batching surface
    # the reference lacks; also exercises the m>1 kernel paths at scale)
    lanes_tok_s = None
    n_lanes = int(os.environ.get("BENCH_BATCH", "4"))
    if n_lanes > 1 and not os.environ.get("BENCH_CPU_FALLBACK"):
        del cache
        cache_l = init_kv_cache(h, batch_size=n_lanes, dtype=kv_dtype)
        cache_l = {
            k: jax.device_put(v, NamedSharding(mesh, cspecs[k]))
            for k, v in cache_l.items()
        }

        tok_l = jax.device_put(
            jnp.ones((n_lanes, 1), jnp.int32), token_sharding
        )
        tok_l, cache_l = decode_block(
            params, tok_l, cache_l, steps, jnp.int32(0)
        )
        _ = np.asarray(tok_l)  # compile + warmup
        t0 = time.perf_counter()
        tok_l, cache_l = decode_block(
            params, tok_l, cache_l, steps, jnp.int32(steps)
        )
        _ = np.asarray(tok_l)
        dt_l = time.perf_counter() - t0
        lanes_tok_s = n_lanes * steps / dt_l / tp
        log(f"{n_lanes}-lane decode: {lanes_tok_s:.2f} aggregate tok/s/chip "
            f"({lanes_tok_s / per_chip:.2f}x single-stream)")

    # staged weight-format sweep (BENCH_SWEEP_FORMATS=1): after the
    # headline format, rebuild params in each OTHER quantized device
    # format and run one timed decode block — a single silicon session
    # then ranks q40 (int8 unpack) vs q40i8 (MXU integer dots) vs q40i4
    # (packed nibbles, in-kernel unpack) on identical shapes. Stages run
    # serially and free the previous format's params first, so HBM holds
    # one weight copy at a time.
    sweep_results = {}
    if os.environ.get("BENCH_SWEEP_FORMATS") and not os.environ.get(
        "BENCH_CPU_FALLBACK"
    ):
        for fmt in ("q40", "q40i8", "q40i4"):
            if fmt == weight_format:
                sweep_results[fmt] = round(per_chip, 2)  # headline run
                continue
            del params
            params = random_params(
                h, dtype=jnp.bfloat16, mesh=mesh, weight_format=fmt,
                fuse=tp,
            )
            cache_f = init_kv_cache(h, batch_size=1, dtype=kv_dtype)
            cache_f = {
                k: jax.device_put(v, NamedSharding(mesh, cspecs[k]))
                for k, v in cache_f.items()
            }
            tok_f = jax.device_put(
                jnp.asarray([[1]], dtype=jnp.int32), token_sharding
            )
            tok_f, cache_f = decode_block(
                params, tok_f, cache_f, steps, jnp.int32(0)
            )
            _ = np.asarray(tok_f)  # compile + warmup
            t0 = time.perf_counter()
            tok_f, cache_f = decode_block(
                params, tok_f, cache_f, steps, jnp.int32(steps)
            )
            _ = np.asarray(tok_f)
            sweep_results[fmt] = round(
                steps / (time.perf_counter() - t0) / tp, 2
            )
            log(f"sweep {fmt}: {sweep_results[fmt]} tok/s/chip")
            del cache_f

    # serving-load smoke (BENCH_SERVING=N concurrent streams through the
    # real HTTP server; tiny synthetic model, so it rides any preset)
    serving = None
    n_serving = int(os.environ.get("BENCH_SERVING", "0"))
    if n_serving > 0:
        serving = _serving_smoke(n_serving)
        log(f"serving smoke: {serving}")

    if _wall_timer is not None:
        _wall_timer.cancel()  # exactly ONE JSON line on a healthy run
    result = dict(_partial_result)
    if serving is not None:
        result["serving"] = serving
    if ttft_p50 is not None:
        result["ttft_ms_p50"] = round(ttft_p50, 1)
    if lanes_tok_s is not None:
        result[f"lanes{n_lanes}_tok_s_per_chip"] = round(lanes_tok_s, 2)
    if sweep_results:
        result["format_sweep_tok_s_per_chip"] = sweep_results
    print(json.dumps(result))
    write_bench_summaries(result)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # emit an honest record instead of a bare crash
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "bench_error",
                    "value": 0.0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": None,
                    "comparable": False,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(1)  # record printed, but CI/validation must still see red
