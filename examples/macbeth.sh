#!/bin/bash
# Long-generation determinism check — the TPU port of the reference's
# examples/macbeth.sh: greedy-decode a long continuation twice and require
# byte-identical output (catches nondeterministic kernels/collectives).
#
# Usage: ./macbeth.sh <model.m> <tokenizer.t> [steps]

set -e -o pipefail
MODEL=${1:?usage: macbeth.sh <model.m> <tokenizer.t> [steps]}
TOK=${2:?tokenizer path required}
STEPS=${3:-128}

REPO="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

PROMPT="Tomorrow, and tomorrow, and tomorrow, Creeps in this petty pace from day to day"

run() {
    python -m dllama_tpu inference \
        --model "$MODEL" --tokenizer "$TOK" --tp "${TP:-1}" \
        --prompt "$PROMPT" --steps "$STEPS" --temperature 0.0 \
        2>/dev/null | grep '^🔶' | sed 's/.*| //'
}

A=$(run)
B=$(run)
if [ -z "$A" ]; then
    echo "❌ no output produced (CLI failed or nothing decoded — is steps > prompt length?)"
    exit 1
fi
if [ "$A" = "$B" ]; then
    echo "✅ deterministic over $STEPS steps"
else
    echo "❌ outputs differ between runs"
    diff <(echo "$A") <(echo "$B") | head
    exit 1
fi
