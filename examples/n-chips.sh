#!/bin/bash
# Local multi-chip simulation harness — the TPU analogue of the reference's
# examples/n-workers.sh (which spawned W worker processes under `screen` on
# localhost ports): under SPMD there are no worker processes, so an N-chip
# cluster is simulated with N virtual CPU devices in ONE process.
#
# Usage: ./n-chips.sh <n-chips> <model.m> <tokenizer.t> [extra args...]
#
# Extra args win over the defaults (argparse last-wins), so mixed meshes
# run as e.g.:
#   ./n-chips.sh 8 m.m t.t --tp 2 --pp 2 --sp 2        # pp x sp x tp
#   ./n-chips.sh 8 m.m t.t --tp 2 --dp 2 --batch-size 2 # lanes over dp
#   ./n-chips.sh 4 m.m t.t --kv-dtype int8 --weight-format q40i8

set -e
N=${1:?usage: n-chips.sh <n-chips> <model.m> <tokenizer.t> [args...]}
MODEL=${2:?model path required}
TOK=${3:?tokenizer path required}
shift 3

REPO="$(cd "$(dirname "$0")/.." && pwd)"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=$N"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

exec python -m dllama_tpu inference \
    --model "$MODEL" --tokenizer "$TOK" --tp "$N" \
    --prompt "Hello world" --steps 32 --temperature 0.0 --dtype f32 "$@"
