// Minimal API client for the dllama-tpu OpenAI-compatible server
// (port of the reference's examples/chat-api-client.js).
//
// Start the server:
//   python -m dllama_tpu.runtime.api_server --model m.m --tokenizer t.t --port 9990
// Then:  node chat-api-client.js

const HOST = process.env.DLLAMA_HOST || 'localhost';
const PORT = process.env.DLLAMA_PORT || 9990;

async function chat(messages, stream = false) {
    const response = await fetch(`http://${HOST}:${PORT}/v1/chat/completions`, {
        method: 'POST',
        headers: { 'Content-Type': 'application/json' },
        body: JSON.stringify({
            messages,
            temperature: 0.7,
            max_tokens: 128,
            stream,
        }),
    });
    if (!stream) {
        const data = await response.json();
        return data.choices[0].message.content;
    }
    const reader = response.body.getReader();
    const decoder = new TextDecoder();
    let text = '';
    for (;;) {
        const { done, value } = await reader.read();
        if (done) break;
        for (const line of decoder.decode(value).split('\r\n')) {
            if (!line.startsWith('data: ') || line === 'data: [DONE]') continue;
            const chunk = JSON.parse(line.slice(6));
            const delta = chunk.choices[0].delta;
            if (delta && delta.content) {
                process.stdout.write(delta.content);
                text += delta.content;
            }
        }
    }
    process.stdout.write('\n');
    return text;
}

(async () => {
    console.log('non-streaming:');
    console.log(await chat([{ role: 'user', content: 'What is a TPU?' }]));
    console.log('streaming:');
    await chat([{ role: 'user', content: 'Count to five.' }], true);
})();
