// Minimal API client for the dllama-tpu OpenAI-compatible server
// (port of the reference's examples/chat-api-client.js).
//
// Start the server:
//   python -m dllama_tpu.runtime.api_server --model m.m --tokenizer t.t --port 9990
// Then:  node chat-api-client.js
//
// Responses carry a `dllama` metadata object (request_id, lane, ttft_ms,
// queue_ms, reused_prefix_tokens) — on the non-stream response body, and
// on the FINAL chunk of an SSE stream. The request_id matches the
// server's --trace-out JSONL records, so a slow request spotted here can
// be looked up in the trace.

const HOST = process.env.DLLAMA_HOST || 'localhost';
const PORT = process.env.DLLAMA_PORT || 9990;

function printMeta(meta) {
    if (!meta) return; // older server without the obs subsystem
    console.log(
        `   [${meta.request_id}] ttft=${meta.ttft_ms}ms ` +
        `queue=${meta.queue_ms}ms reused_prefix=${meta.reused_prefix_tokens}`);
}

async function chat(messages, stream = false) {
    const response = await fetch(`http://${HOST}:${PORT}/v1/chat/completions`, {
        method: 'POST',
        headers: { 'Content-Type': 'application/json' },
        body: JSON.stringify({
            messages,
            temperature: 0.7,
            max_tokens: 128,
            stream,
        }),
    });
    if (!stream) {
        const data = await response.json();
        printMeta(data.dllama);
        return data.choices[0].message.content;
    }
    const reader = response.body.getReader();
    const decoder = new TextDecoder();
    let text = '';
    for (;;) {
        const { done, value } = await reader.read();
        if (done) break;
        for (const line of decoder.decode(value).split('\r\n')) {
            if (!line.startsWith('data: ') || line === 'data: [DONE]') continue;
            const chunk = JSON.parse(line.slice(6));
            const delta = chunk.choices[0].delta;
            if (delta && delta.content) {
                process.stdout.write(delta.content);
                text += delta.content;
            }
            // the final chunk (the one carrying finish_reason) also
            // carries the request's timing metadata
            if (chunk.choices[0].finish_reason) {
                process.stdout.write('\n');
                printMeta(chunk.dllama);
            }
        }
    }
    return text;
}

(async () => {
    console.log('non-streaming:');
    console.log(await chat([{ role: 'user', content: 'What is a TPU?' }]));
    console.log('streaming:');
    await chat([{ role: 'user', content: 'Count to five.' }], true);
})();
