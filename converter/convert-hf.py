#!/usr/bin/env python
"""Convert a HuggingFace safetensors checkpoint to a distributed-llama `.m` file.

Same CLI and output as the reference converter (converter/convert-hf.py):

    python convert-hf.py <sourceFolderPath> <weightsFloatType> <name>

Supported architectures: llama / mistral (LLAMA), qwen3, qwen3_moe.
Tensor order and quantization are byte-compatible with the reference (the
reader in dllama_tpu.formats consumes either converter's output).

Fresh implementation on numpy + safetensors (no torch dependency): tensors
stream one at a time, so host memory stays at one tensor regardless of
checkpoint size.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.formats.quants import FloatType, parse_float_type  # noqa: E402
from dllama_tpu.formats.writer import write_header, write_tensor  # noqa: E402

ARCH_TYPES = {
    "llama": 0xABCD00,
    "mistral": 0xABCD00,
    "qwen3": 0xABCD01,
    "qwen3_moe": 0xABCD02,
}
HIDDEN_ACTS = {"gelu": 0, "silu": 1}


def permute_rows(tensor: np.ndarray, n_heads: int) -> np.ndarray:
    """Re-order q/k projection rows from HF half-rotation layout to the
    interleaved-rope layout (reference: convert-hf.py:13-16)."""
    out_dim = tensor.shape[0]
    return (
        tensor.reshape(n_heads, 2, out_dim // n_heads // 2, *tensor.shape[1:])
        .swapaxes(1, 2)
        .reshape(tensor.shape)
    )


def parse_rms_norm_epsilon(eps: float) -> int:
    if eps == 1e-5:
        return 5
    if eps == 1e-6:
        return 6
    raise ValueError(f"unsupported epsilon: {eps}")


def load_config(folder: str, weights_float_type: int) -> dict:
    with open(os.path.join(folder, "config.json")) as f:
        config = json.load(f)
    arch = ARCH_TYPES.get(config["model_type"])
    if arch is None:
        raise ValueError(f"unsupported arch type: {config['model_type']}")
    result = {
        "version": 0,
        "arch_type": arch,
        "hidden_act": HIDDEN_ACTS[config["hidden_act"]],
        "dim": config["hidden_size"],
        "hidden_dim": config["intermediate_size"],
        "n_layers": config["num_hidden_layers"],
        "n_heads": config["num_attention_heads"],
        "n_kv_heads": config["num_key_value_heads"],
        "weights_float_type": weights_float_type,
        "max_seq_len": config["max_position_embeddings"],
        "vocab_size": config["vocab_size"],
    }
    result["n_experts"] = int(config.get("num_experts") or 0)
    result["n_active_experts"] = int(config.get("num_experts_per_tok") or 0)
    if config.get("rope_theta") is not None:
        result["rope_theta"] = int(config["rope_theta"])
    scaling = config.get("rope_scaling")
    if scaling is not None:
        if scaling.get("rope_type") != "llama3":
            raise ValueError(f"unsupported rope type: {scaling.get('rope_type')}")
        result["rope_scaling_factor"] = int(scaling["factor"])
        result["rope_scaling_low_freq_factor"] = int(scaling["low_freq_factor"])
        result["rope_scaling_high_freq_factory"] = int(scaling["high_freq_factor"])
        result["rope_scaling_orig_max_seq_len"] = int(
            scaling["original_max_position_embeddings"]
        )
        result["rope_type"] = 2  # LLAMA3_1
    if config.get("head_dim") is not None:
        result["head_dim"] = config["head_dim"]
    if config.get("rms_norm_eps") is not None:
        result["norm_epsilon"] = parse_rms_norm_epsilon(config["rms_norm_eps"])
    if config.get("moe_intermediate_size") is not None:
        result["moe_hidden_dim"] = int(config["moe_intermediate_size"])
    return result


class SafetensorsIndex:
    """name -> (file, lazy tensor) across all shards, loaded one file at a
    time in name-lookup order (the reference walks files the same way)."""

    def __init__(self, folder: str):
        from safetensors import safe_open

        self.files = sorted(
            os.path.join(folder, f)
            for f in os.listdir(folder)
            if f.endswith(".safetensors") and not f.startswith(".")
        )
        if not self.files:
            raise FileNotFoundError("no .safetensors files found")
        self.location: dict[str, str] = {}
        for path in self.files:
            with safe_open(path, framework="np") as f:
                for key in f.keys():
                    self.location[key] = path
        self._open_path: str | None = None
        self._open = None

    def get(self, *names: str) -> tuple[str, np.ndarray]:
        from safetensors import safe_open

        for name in names:
            path = self.location.get(name)
            if path is None:
                continue
            if path != self._open_path:
                self._open = safe_open(path, framework="np")
                self._open_path = path
            return name, self._open.get_tensor(name)
        raise KeyError(f"tensor not found: {names[0]}")


def tensor_plan(config: dict, wt: int) -> list[tuple]:
    """(float_type, transform?, *lookup_names) in file order
    (reference: convert-hf.py:59-104)."""
    arch = config["arch_type"]
    n_heads = config["n_heads"]
    plan: list[tuple] = [(FloatType.F32, None, "model.embed_tokens.weight")]
    is_llama = arch == ARCH_TYPES["llama"]
    q_perm = (lambda t: permute_rows(t, n_heads)) if is_llama else None
    k_perm = (
        (lambda t: permute_rows(t, config["n_kv_heads"])) if is_llama else None
    )
    for l in range(config["n_layers"]):
        plan.append((wt, q_perm, f"model.layers.{l}.self_attn.q_proj.weight"))
        plan.append((wt, k_perm, f"model.layers.{l}.self_attn.k_proj.weight"))
        plan.append((wt, None, f"model.layers.{l}.self_attn.v_proj.weight"))
        plan.append((wt, None, f"model.layers.{l}.self_attn.o_proj.weight"))
        if config["n_experts"] > 0:
            plan.append((FloatType.F32, None, f"model.layers.{l}.mlp.gate.weight"))
            for e in range(config["n_experts"]):
                plan.append((wt, None, f"model.layers.{l}.mlp.experts.{e}.gate_proj.weight"))
                plan.append((wt, None, f"model.layers.{l}.mlp.experts.{e}.down_proj.weight"))
                plan.append((wt, None, f"model.layers.{l}.mlp.experts.{e}.up_proj.weight"))
        else:
            plan.append((wt, None, f"model.layers.{l}.mlp.gate_proj.weight"))
            plan.append((wt, None, f"model.layers.{l}.mlp.down_proj.weight"))
            plan.append((wt, None, f"model.layers.{l}.mlp.up_proj.weight"))
        if arch in (ARCH_TYPES["qwen3"], ARCH_TYPES["qwen3_moe"]):
            plan.append((FloatType.F32, None, f"model.layers.{l}.self_attn.q_norm.weight"))
            plan.append((FloatType.F32, None, f"model.layers.{l}.self_attn.k_norm.weight"))
        plan.append((FloatType.F32, None, f"model.layers.{l}.input_layernorm.weight"))
        plan.append((FloatType.F32, None, f"model.layers.{l}.post_attention_layernorm.weight"))
    plan.append((FloatType.F32, None, "model.norm.weight"))
    # lm_head falls back to tied embeddings (reference: convert-hf.py:103-104)
    plan.append((wt, None, "lm_head.weight", "model.embed_tokens.weight"))
    return plan


def convert(folder: str, weights_float_type: FloatType, output_path: str) -> None:
    config = load_config(folder, int(weights_float_type))
    index = SafetensorsIndex(folder)
    with open(output_path, "wb") as out:
        write_header(out, config)
        for item in tensor_plan(config, int(weights_float_type)):
            ft, transform, *lookup = item
            name, tensor = index.get(*lookup)
            tensor = np.asarray(tensor, dtype=np.float32)
            print(f"🔶 Writing tensor {name} {tensor.shape}...")
            if transform is not None:
                tensor = transform(tensor)
            write_tensor(out, tensor, FloatType(ft))


def print_usage():
    print("Usage: python convert-hf.py <sourceFolderPath> <weightsFloatType> <name>")
    print()
    print("Options:")
    print("  <sourceFolderPath> The path to the folder containing the model files")
    print('  <weightsFloatType> The float type of the weights (e.g. "q40")')
    print('  <name>             The name of the model (e.g. "llama3")')


if __name__ == "__main__":
    if len(sys.argv) < 4:
        print_usage()
        sys.exit(1)
    folder = sys.argv[1]
    weights_float_type = parse_float_type(sys.argv[2])
    name = sys.argv[3]
    output = f"dllama_model_{name}_{sys.argv[2]}.m"
    print(f"Output file: {output}")
    convert(folder, weights_float_type, output)
    print(f"✅ {output} created successfully")
