#!/usr/bin/env python
"""Convert original Meta Llama `.pth` checkpoints (consolidated.*.pth) to `.m`.

Same CLI and output as the reference (converter/convert-llama.py):

    python convert-llama.py <modelPath> <targetFloatType>

Slices are concatenated across consolidated files on the original
megatron-style split axes: axis 1 for tok_embeddings/wo/w2, axis 0 for the
row-parallel projections. Needs torch (CPU) to read the pickle files.
"""

from __future__ import annotations

import json
import math
import os
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.formats.quants import FloatType, float_type_name, parse_float_type  # noqa: E402
from dllama_tpu.formats.writer import write_header, write_tensor  # noqa: E402

LAYER_CHUNK_SIZE = 48


def layer_names(n_layers: int) -> list[str]:
    names = ["tok_embeddings.weight"]
    for l in range(n_layers):
        names += [
            f"layers.{l}.attention.wq.weight",
            f"layers.{l}.attention.wk.weight",
            f"layers.{l}.attention.wv.weight",
            f"layers.{l}.attention.wo.weight",
            f"layers.{l}.feed_forward.w1.weight",
            f"layers.{l}.feed_forward.w2.weight",
            f"layers.{l}.feed_forward.w3.weight",
            f"layers.{l}.attention_norm.weight",
            f"layers.{l}.ffn_norm.weight",
        ]
    names += ["norm.weight", "output.weight"]
    return names


def convert(model_path: str, output_path: str, target: FloatType) -> None:
    import torch

    with open(os.path.join(model_path, "params.json")) as f:
        params = json.load(f)
    if params["vocab_size"] < 1:
        raise SystemExit("vocab_size is invalid, please update params.json file")
    if params.get("max_seq_len") is None:
        raise SystemExit("max_seq_len is required, please update params.json file")

    header = {
        "version": 0,
        "arch_type": 0xABCD00,
        "dim": params["dim"],
        "n_layers": params["n_layers"],
        "n_heads": params["n_heads"],
        "n_kv_heads": params.get("n_kv_heads") or params["n_heads"],
        "n_experts": 0,
        "n_active_experts": 0,
        "vocab_size": params["vocab_size"],
        "max_seq_len": params["max_seq_len"],
        "weights_float_type": int(target),
    }
    if "rope_theta" in params:
        header["rope_theta"] = int(params["rope_theta"])

    model_paths = sorted(Path(model_path).glob("consolidated.*.pth"))
    n_slices = len(model_paths)
    if n_slices == 0:
        raise SystemExit("no consolidated.*.pth files found")

    names = layer_names(params["n_layers"])
    header_written = False

    with open(output_path, "wb") as out:
        n_chunks = math.ceil(len(names) / LAYER_CHUNK_SIZE)
        for chunk_index in range(n_chunks):
            chunk = names[LAYER_CHUNK_SIZE * chunk_index : LAYER_CHUNK_SIZE * (chunk_index + 1)]
            collected: dict[str, list] = {n: [] for n in chunk}
            print(f"💿 Chunking model {chunk_index + 1}/{n_chunks}...")
            for path in model_paths:
                model = torch.load(path, map_location="cpu", weights_only=True)
                for key in model:
                    if key in collected:
                        collected[key].append(model[key])
                if not header_written:
                    header["hidden_dim"] = (
                        model["layers.0.feed_forward.w1.weight"].shape[0] * n_slices
                    )
                    write_header(out, header)
                    header_written = True
                del model

            for name in chunk:
                if name == "rope.freqs":
                    continue
                is_axis1 = (
                    name == "tok_embeddings.weight"
                    or name.endswith(".attention.wo.weight")
                    or name.endswith(".feed_forward.w2.weight")
                )
                is_always_f32 = (
                    name == "tok_embeddings.weight"
                    or name.endswith(".attention_norm.weight")
                    or name.endswith(".ffn_norm.weight")
                    or name == "norm.weight"
                )
                ft = FloatType.F32 if is_always_f32 else target
                tensors = collected[name]
                if len(tensors) == 1 or tensors[0].dim() == 1:
                    merged = tensors[0]
                else:
                    merged = torch.cat(tensors, dim=1 if is_axis1 else 0)
                print(f"🔶 Exporting {name} {tuple(merged.shape)}...")
                write_tensor(
                    out, merged.to(torch.float32).numpy().astype(np.float32), ft
                )


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print("Usage: python convert-llama.py <modelPath> <targetFloatType>")
        sys.exit(1)
    model_path = sys.argv[1]
    target = parse_float_type(sys.argv[2])
    model_name = os.path.basename(model_path)
    output = f"dllama_model_{model_name.lower()}_{float_type_name(target)}.m"
    print(f"Model name: {model_name}")
    print(f"Target float type: {float_type_name(target)}")
    print(f"Target file: {output}")
    convert(model_path, output, target)
    print("Done!")
