#!/usr/bin/env python
"""Convert the original Llama 2 sentencepiece tokenizer.model to `.t`.

Same CLI and output as the reference (converter/convert-tokenizer-llama2.py):

    python convert-tokenizer-llama2.py <llama2FolderPath>

Requires the sentencepiece package (gated: not installed in every image).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer  # noqa: E402

CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}{% set loop_messages = messages[1:] %}"
    "{% set system_message = messages[0]['content'] %}{% else %}"
    "{% set loop_messages = messages %}{% set system_message = false %}{% endif %}"
    "{% for message in loop_messages %}"
    "{% if (message['role'] == 'user') != (loop.index0 % 2 == 0) %}"
    "{{ raise_exception('Conversation roles must alternate user/assistant/user/assistant/...') }}"
    "{% endif %}{% if loop.index0 == 0 and system_message != false %}"
    "{% set content = '<<SYS>>\\n' + system_message + '\\n<</SYS>>\\n\\n' + message['content'] %}"
    "{% else %}{% set content = message['content'] %}{% endif %}"
    "{% if message['role'] == 'user' %}{{ bos_token + '[INST] ' + content.strip() + ' [/INST]' }}"
    "{% elif message['role'] == 'assistant' %}{{ ' '  + content.strip() + ' ' + eos_token }}"
    "{% endif %}{% endfor %}"
)


def main() -> None:
    if len(sys.argv) < 2:
        print("Usage: python convert-tokenizer-llama2.py <llama2FolderPath>")
        sys.exit(1)
    try:
        from sentencepiece import SentencePieceProcessor
    except ImportError:
        raise SystemExit(
            "convert-tokenizer-llama2.py needs the sentencepiece package "
            "(not installed in this environment)"
        )
    processor = SentencePieceProcessor(
        model_file=os.path.join(sys.argv[1], "tokenizer.model")
    )
    tokens: list[bytes] = []
    scores: list[float] = []
    for i in range(processor.vocab_size()):
        piece = processor.id_to_piece(i).replace("▁", " ")
        tokens.append(piece.encode("utf-8"))
        scores.append(processor.get_score(i))
    output = "dllama_tokenizer_llama2.t"
    write_tokenizer(
        output,
        TokenizerData(
            vocab=tokens,
            scores=scores,
            bos_id=processor.bos_id(),
            add_bos=True,
            eos_token_ids=[processor.eos_id()],
            chat_template=CHAT_TEMPLATE,
            max_token_length=max(len(t) for t in tokens),
        ),
    )
    print(f"✅ Created {output}")


if __name__ == "__main__":
    main()
