#!/usr/bin/env python
"""Convert the original Llama 3 tiktoken-style tokenizer.model to `.t`.

Same CLI and output as the reference (converter/convert-tokenizer-llama3.py):

    python convert-tokenizer-llama3.py <tokenizerPath>

Input lines are `base64token rank`; scores are negated ranks; the 256
reserved special tokens and the llama3 chat template are appended.
"""

from __future__ import annotations

import base64
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer  # noqa: E402

N_SPECIAL_TOKENS = 256
SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
] + [f"<|reserved_special_token_{i}|>" for i in range(5, N_SPECIAL_TOKENS - 5)]
BOS_ID = 128000
EOS_ID = 128001
CHAT_EOS_ID = 128009
CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}{% if loop.index0 == 0 %}"
    "{% set content = bos_token + content %}{% endif %}{{ content }}{% endfor %}"
    "{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
)


def main() -> None:
    if len(sys.argv) < 2:
        print("Usage: python convert-tokenizer-llama3.py <tokenizerPath>")
        sys.exit(1)
    tokens: list[bytes] = []
    scores: list[float] = []
    with open(sys.argv[1]) as f:
        for line in f:
            b64, rank = line.split(" ")
            tokens.append(base64.b64decode(b64))
            scores.append(-float(rank))
    index = len(tokens)
    for tok in SPECIAL_TOKENS:
        tokens.append(tok.encode("utf-8"))
        scores.append(-float(index))
        index += 1
    output = "dllama_tokenizer_llama3.t"
    write_tokenizer(
        output,
        TokenizerData(
            vocab=tokens,
            scores=scores,
            bos_id=BOS_ID,
            add_bos=True,
            eos_token_ids=[EOS_ID, CHAT_EOS_ID],
            chat_template=CHAT_TEMPLATE,
            max_token_length=max(len(t) for t in tokens),
        ),
    )
    print(f"✅ Created {output}")


if __name__ == "__main__":
    main()
