#!/usr/bin/env python
"""Convert a HuggingFace tokenizer folder to a distributed-llama `.t` file.

Same CLI and output as the reference (converter/convert-tokenizer-hf.py):

    python convert-tokenizer-hf.py <tokenizerFolderPath> <name>

Handles fast tokenizers (tokenizer.json; GPT-2 byte-to-unicode inversion,
scores = -token_id so lower ids merge first) and sentencepiece
LlamaTokenizer models (gated on the sentencepiece package).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer  # noqa: E402


def unicode_to_bytes() -> dict[str, int]:
    """Inverse of GPT-2's byte-to-unicode table
    (reference: convert-tokenizer-hf.py:12-23)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(2**8):
        if b not in bs:
            bs.append(b)
            cs.append(2**8 + n)
            n += 1
    return dict(zip((chr(c) for c in cs), bs))


def resolve_fast_tokenizer(dir_path: str) -> tuple[list[bytes], list[float], int | None, list[int] | None]:
    from transformers import PreTrainedTokenizerFast

    utb = unicode_to_bytes()
    tokenizer = PreTrainedTokenizerFast(
        tokenizer_file=os.path.join(dir_path, "tokenizer.json")
    )
    vocab_len = len(tokenizer.get_vocab())
    tokens: list[bytes] = []
    scores: list[float] = []
    for i in range(vocab_len):
        token_chars = list(tokenizer.convert_ids_to_tokens([i])[0])
        token_bytes: list[int] = []
        for ch in token_chars:
            if ch in utb:
                token_bytes.append(utb[ch])
            else:
                token_bytes += list(ch.encode("utf-8"))
        tokens.append(bytes(token_bytes))
        scores.append(-float(i))
    bos_id = tokenizer.bos_token_id
    eos_ids = [tokenizer.eos_token_id] if tokenizer.eos_token_id else None
    return tokens, scores, bos_id, eos_ids


def resolve_sentencepiece(dir_path: str):
    try:
        from sentencepiece import SentencePieceProcessor
    except ImportError:
        raise SystemExit(
            "LlamaTokenizer conversion needs the sentencepiece package "
            "(not installed in this environment); convert the fast-tokenizer "
            "variant (tokenizer.json) instead"
        )
    processor = SentencePieceProcessor(
        model_file=os.path.join(dir_path, "tokenizer.model")
    )
    tokens, scores = [], []
    for i in range(processor.vocab_size()):
        t = processor.id_to_piece(i).replace("▁", " ")
        if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
            b = bytes(bytearray.fromhex(t[3:-1]))
        else:
            b = t.encode("utf-8")
        tokens.append(b)
        scores.append(processor.get_score(i))
    return tokens, scores, processor.bos_id(), [processor.eos_id()]


def main() -> None:
    if len(sys.argv) < 3:
        print("Usage: python convert-tokenizer-hf.py <tokenizerFolderPath> <name>")
        sys.exit(1)
    dir_path, name = sys.argv[1], sys.argv[2]
    with open(os.path.join(dir_path, "tokenizer_config.json")) as f:
        tokenizer_config = json.load(f)

    cls = tokenizer_config["tokenizer_class"]
    if cls in ("PreTrainedTokenizerFast", "LlamaTokenizerFast", "Qwen2Tokenizer"):
        tokens, scores, bos_id, eos_ids = resolve_fast_tokenizer(dir_path)
    elif cls == "LlamaTokenizer":
        tokens, scores, bos_id, eos_ids = resolve_sentencepiece(dir_path)
    else:
        raise SystemExit(f"Tokenizer {cls} is not supported")

    if bos_id is None or eos_ids is None:
        with open(os.path.join(dir_path, "config.json")) as f:
            config = json.load(f)
        if bos_id is None:
            bos_id = config["bos_token_id"]
        if eos_ids is None:
            eos = config["eos_token_id"]
            eos_ids = eos if isinstance(eos, list) else [eos]
    if bos_id is None or eos_ids is None:
        raise SystemExit("Cannot resolve bosId or eosIds")

    print(f"bosId: {bos_id} ({tokens[bos_id]})")
    for eos_id in eos_ids:
        print(f"eosId: {eos_id} ({tokens[eos_id]})")

    data = TokenizerData(
        vocab=tokens,
        scores=scores,
        bos_id=bos_id,
        add_bos=bool(tokenizer_config.get("add_bos_token", True)),
        eos_token_ids=eos_ids,
        chat_template=tokenizer_config.get("chat_template"),
        max_token_length=max(len(t) for t in tokens),
    )
    output = f"dllama_tokenizer_{name}.t"
    write_tokenizer(output, data)
    print(f"✅ Created {output}")


if __name__ == "__main__":
    main()
